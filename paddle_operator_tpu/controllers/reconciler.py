"""The TpuJob reconcile loop.

Reference: ``controllers/paddlejob_controller.go:101-333`` — the same
level-triggered shape: derive status from child pods, then converge the
world. Deletions stay one-per-pass (the reference's cadence: remove at most
one object, let the next event-driven pass continue); CREATIONS diverge —
all missing Services and the whole pod gang go in a single pass, because the
write-through informer cache gives read-your-writes safety and on TPU the
gang's bring-up latency is idle-slice time. Other TPU-native behavior
differences are called out inline.
"""

from __future__ import annotations

import json
import logging
import threading
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api import types as api
from ..elastic.store import KVStore
from ..elastic.sync import bump_epoch, sync_np
from ..k8s import objects as k8s
from ..k8s.client import EventRecorder, KubeClient
from ..k8s.errors import ApiError, ConflictError, NotFoundError
from ..obs import JobMetrics, ObservedEventRecorder, incident_cause
from ..serving import controller as serving_ctrl
from ..utils.trace import SpanContext, tracer
from . import helper
from .hostport import PortRangeAllocator

log = logging.getLogger("tpujob.reconciler")


@dataclass
class Result:
    requeue: bool = False
    requeue_after: Optional[float] = None

    @property
    def needs_requeue(self) -> bool:
        return self.requeue or self.requeue_after is not None


class TpuJobReconciler:
    """Reconciles TpuJob objects against the cluster state."""

    def __init__(
        self,
        client: KubeClient,
        recorder: Optional[EventRecorder] = None,
        scheduling: str = "",
        init_image: str = "docker.io/library/busybox:1",
        port_allocator: Optional[PortRangeAllocator] = None,
        kv_store: Optional[KVStore] = None,
        coordination_url: str = "",
        backoff_base: float = 1.0,
        backoff_cap: float = 30.0,
        job_metrics: Optional[JobMetrics] = None,
        arbiter=None,
    ):
        self.client = client
        # Fleet capacity arbiter (sched.FleetArbiter or None). When set,
        # its decide() gates pod creation where the first-come gang gate
        # used to: jobs wait for fleet capacity instead of racing for it,
        # and the arbiter drives shrink/preempt through this reconciler's
        # existing elastic-resize and graceful-drain paths.
        self.arbiter = arbiter
        # last SchedQueued reason evented per job (the queue decision
        # repeats every requeue pass; the Event must not). Shared with
        # _exec_release_warned under _warn_lock: per-key workqueue
        # exclusivity serializes same-key passes, but with
        # --reconcile-workers > 1 DIFFERENT keys mutate these tables
        # concurrently.
        self._warn_lock = threading.Lock()
        self._sched_queued: Dict[Tuple[str, str], str] = {}
        # Hard-preemption incident dedup by pod uid (per job): under a
        # dropped watch the informer cache can keep serving a Failed pod
        # this process already deleted — "not already deleting" is a
        # stale-cache-defeatable proxy, and re-counting the same pod
        # burns the whole restart budget on ONE kill. A recreated pod
        # carries a fresh uid, so legitimate re-kills still count; a
        # restarted operator re-lists into a fresh cache, so losing this
        # memory is safe (the pod is either really gone or really fresh).
        self._preempt_handled: Dict[Tuple[str, str], set] = {}
        # Per-job observability collector: phase gauges/histograms,
        # cause-split restart counters, goodput ledger, flight recorder.
        # Whoever owns the Manager registers ``self.obs.metrics_block``
        # as a provider.
        self.obs = job_metrics if job_metrics is not None else JobMetrics()
        # every Event also lands in the flight recorder + process trace
        self.recorder = ObservedEventRecorder(
            recorder or EventRecorder(client, "tpujob-controller"), self.obs)
        # the goodput ledger's alert channel (backend-degradation
        # detector): alerts surface as Warning Events on the job, exactly
        # like any other reconciler-emitted incident
        if self.obs.ledger.on_alert is None:
            self.obs.ledger.on_alert = self._obs_alert
        self.scheduling = scheduling
        self.init_image = init_image
        self.ports = port_allocator
        self.kv = kv_store
        # Base URL of the operator's HTTP coordination endpoint (see
        # controllers/coordination.py). When set, coord init containers pull
        # their release decision over HTTP and the exec channel is never
        # used; when empty, the legacy exec-push release applies (fake-client
        # harness parity only — HttpKubeClient cannot exec).
        self.coordination_url = coordination_url
        # jobs already warned about exec-release failure: the failure
        # repeats every 1s requeue pass, the Event must not (apiserver flood)
        self._exec_release_warned: set = set()
        # Error-path requeue backoff: consecutive failing passes on the
        # same key escalate requeue_after exponentially (base*2^n, capped)
        # with deterministic jitter, instead of the old fixed 1.0s — under
        # a flaking apiserver a fixed cadence hammers it in lockstep.
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        # streak table is written by the worker thread and READ by the
        # /metrics scrape thread (current_backoff as a workqueue gauge):
        # iteration during concurrent insert raises, so all access locks
        self._err_lock = threading.Lock()
        self._err_streak: Dict[Tuple[str, str], int] = {}
        self._err_hit: set = set()

    def _obs_alert(self, namespace: str, name: str, reason: str,
                   message: str) -> None:
        """Detector alerts (obs.GoodputLedger) become Warning Events on
        the job: a reference object is enough — the EventRecorder only
        reads kind + metadata for involvedObject."""
        ref = {"kind": api.KIND, "apiVersion": api.API_VERSION,
               "metadata": {"namespace": namespace, "name": name}}
        try:
            self.recorder.event(ref, "Warning", reason, message)
        except Exception as e:  # an alert must never take training down
            log.error("obs alert event failed for %s/%s: %s",
                      namespace, name, e)

    # ------------------------------------------------------------------
    # error-requeue backoff
    # ------------------------------------------------------------------

    def _backoff_for(self, key: Tuple[str, str], n: int) -> float:
        # cap the exponent BEFORE 2**: a key failing for days would
        # otherwise grow a multi-kilobyte big int per pass just to be
        # discarded by min()
        base = min(self.backoff_base * (2 ** min(n - 1, 32)),
                   self.backoff_cap)
        # jitter must be deterministic (chaos runs replay byte-identically
        # from a seed), so derive it from (key, streak), not a global rng
        salt = zlib.crc32(("%s/%s#%d" % (key[0], key[1], n)).encode())
        return base * (0.5 + 0.5 * (salt % 1000) / 999.0)

    def _requeue_error(self, key: Tuple[str, str]) -> Result:
        """An error-path requeue: escalate this key's streak and park it
        for the backed-off delay. The wrapper resets the streak on the
        first pass that completes without calling this."""
        with self._err_lock:
            self._err_hit.add(key)
            n = self._err_streak.get(key, 0) + 1
            self._err_streak[key] = n
        return Result(requeue_after=self._backoff_for(key, n))

    def current_backoff(self) -> float:
        """Max armed error-requeue backoff in seconds (workqueue gauge)."""
        with self._err_lock:
            streaks = list(self._err_streak.items())
        out = 0.0
        for key, n in streaks:
            out = max(out, self._backoff_for(key, n))
        return out

    # ------------------------------------------------------------------
    # main loop
    # ------------------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> Result:
        key = (namespace, name)
        with self._err_lock:
            self._err_hit.discard(key)
        try:
            result = self._reconcile(namespace, name)
        except Exception:
            # a panicking pass keeps its streak: the Controller's own retry
            # backoff requeues it, and the NEXT error-path requeue must
            # start from the escalated delay, not from scratch
            with self._err_lock:
                self._err_streak[key] = self._err_streak.get(key, 0) + 1
            raise
        with self._err_lock:
            if key not in self._err_hit:
                self._err_streak.pop(key, None)
        return result

    def _reconcile(self, namespace: str, name: str) -> Result:
        try:
            obj = self.client.get(api.KIND, namespace, name)
        except NotFoundError:
            # Job is gone: drop its warn-once marker so memory stays bounded
            # across job churn and a recreated same-name job warns afresh.
            with self._warn_lock:
                self._exec_release_warned.discard((namespace, name))
                self._sched_queued.pop((namespace, name), None)
                self._preempt_handled.pop((namespace, name), None)
            self.obs.forget_job(namespace, name)
            if self.arbiter is not None:
                try:
                    # per-job decision counters / own-write ledger /
                    # feedback state: bounded across job churn
                    self.arbiter.forget_job(namespace, name)
                except Exception as e:
                    log.error("fleet arbiter forget failed: %s", e)
            return Result()
        job = api.TpuJob(obj)

        log.info(
            "reconcile %s/%s version=%s phase=%s",
            namespace, name,
            job.metadata.get("resourceVersion"), job.phase,
        )

        errs = job.validate()
        if errs:
            self.recorder.event(
                job.obj, "Warning", "InvalidSpec", "; ".join(errs)
            )
            return Result()

        if self._finalize(job):
            return Result(requeue_after=1.0)
        if job.metadata.get("deletionTimestamp"):
            return Result()

        child_pods = self.client.list_owned("Pod", job.obj)

        # -- status derivation (reference :122-131) ---------------------
        status_changed = self._sync_current_status(job, child_pods)

        # -- incident-context adoption (operator restart survival) ------
        # A restarted operator loses the incident registry with the rest
        # of its memory; mid-incident, the context it minted survives on
        # the job + pods (ANNOT_TRACE_CONTEXT) — re-adopt it so the
        # causal chain keeps its id across the restart. AFTER status
        # derivation, so the Running gate sees the FRESH phase: a crash
        # that left the persisted phase Running while the pods are
        # already dead must adopt NOW, before the restart hooks below
        # would mint a fresh id and fork the chain. BEFORE observe_phase,
        # so the rebuilt ledger's first phase observation already sees
        # the re-opened episode's pending cause.
        self._adopt_trace_context(job, child_pods)
        # observe the freshly derived phase (no-op when unchanged): this
        # is the one site every phase transition flows through, so the
        # phase gauge / time-in-phase histogram / flight recorder see the
        # same machine the status subresource does
        self.obs.observe_phase(namespace, name, job.phase)
        if status_changed:
            try:
                self.client.update_status(job.obj)
            except ConflictError:
                return self._requeue_error((namespace, name))
            except NotFoundError:
                return Result()

        # keep the job-level trace-context annotation current (stamp
        # while an incident is open, strip once recovered) so a
        # restarted operator adopts the newest incident, not whatever a
        # stale pod annotation remembers
        self._sync_trace_annotation(job)

        # -- serving gang sync (serving/controller.py) ------------------
        # Apply the autoscaler's desired-replica annotation to
        # spec.worker.replicas (clamped to the serving bounds); the
        # ordinary scale-up/scale-down passes below then move the actual
        # pods — serving adds no pod-lifecycle code of its own.
        if job.serving is not None and serving_ctrl.sync_serving_spec(job):
            self.recorder.event(
                job.obj, "Normal", "ServingScale",
                "serving autoscaler: worker replicas -> %d"
                % serving_ctrl.serving_replicas(job.obj))
            try:
                self.client.update(job.obj)
            except ConflictError:
                return self._requeue_error((namespace, name))
            except NotFoundError:
                return Result()
            return Result(requeue_after=0.5)

        # -- elastic preemption: whole-slice restart (SURVEY §7) --------
        if job.elastic is not None:
            gate = self._elastic_preemption(job, child_pods)
            if gate is not None:
                return gate

        # -- fleet arbiter admission (sched/) ---------------------------
        # Replaces the gang gate's first-come ordering: the arbiter packs
        # the whole fleet (priority tiers, weighted fair share, shrink-
        # before-evict) and this gate simply asks whether THIS job's gang
        # may exist right now. Runs before the Volcano gate so a queued
        # job does not even claim a PodGroup.
        if self.arbiter is not None:
            gate = self._sched_gate(job)
            if gate is not None:
                return gate

        # -- feedback remediation (sched/feedback.py) -------------------
        # The observe->decide loop acting: a persistent straggler gets
        # its slow member evicted and re-ganged; a backend-degraded job
        # (silent CPU-fallback) gets a budget-free re-schedule through
        # the same graceful-drain path an arbiter eviction rides.
        if (self.arbiter is not None
                and getattr(self.arbiter, "feedback", None) is not None):
            gate = self._feedback_migration(job, child_pods)
            if gate is not None:
                return gate
            gate = self._feedback_remediation(job, child_pods)
            if gate is not None:
                return gate

        # -- volcano gang gate (reference :133-157) ---------------------
        if self.scheduling == helper.SCHEDULER_VOLCANO and not helper.without_volcano(job):
            gate = self._ensure_podgroup(job)
            if gate is not None:
                return gate

        specs = job.get_specs()

        # -- scale-down: drop pods beyond replicas (reference :161-168) -
        for pod in child_pods:
            res_type, idx = helper.extract_name_index(pod["metadata"]["name"])
            if specs.get(res_type) is not None and idx >= specs[res_type]["replicas"]:
                # stamp the drain ack BEFORE deleting: on a real apiserver
                # the pod lingers Terminating through its grace period, and
                # if replicas rise again meanwhile the index filter in
                # _graceful_drain no longer excludes it — the controller's
                # own delete must never read as a preemption drain
                self._ack_drain(pod)
                self._delete_resource(job, pod)
                return Result(requeue=True)

        # -- per-pod headless services (reference :170-191) -------------
        # Multislice always gets them: the slice-local TPU_WORKER_HOSTNAMES
        # injected per pod are pod DNS names, which only resolve when a
        # headless Service matches the pod's hostname/subdomain.
        svcs: List[dict] = []
        if helper.needs_pod_dns(job):
            svcs = self.client.list_owned("Service", job.obj)
            have = {s["metadata"]["name"] for s in svcs}
            created_svc = False
            for pod in child_pods:
                if pod["metadata"]["name"] in have:
                    continue
                svc = helper.construct_service_for_pod(pod, job.device)
                k8s.set_controller_reference(job.obj, svc)
                self._create_resource(job, svc)
                created_svc = True
            if created_svc:
                return Result()

        # -- host-port block (reference :192-196) -----------------------
        if job.intranet == api.Intranet.HOST:
            if self._alloc_host_port(job):
                return Result(requeue_after=1.0)

        # -- elastic np sync (reference :209-219) -----------------------
        if job.elastic is not None and self.kv is not None:
            try:
                np = sync_np(self.kv, job)
            except Exception as e:  # store unreachable — surface and retry
                log.error("elastic sync failed: %s", e)
                return self._requeue_error((namespace, name))
            if np is not None:
                self.obs.observe_resize(namespace, name, np=np)
                self.recorder.event(
                    job.obj, "Normal", "Scaled", "scaled replicas to %s" % np
                )
                return Result(requeue=True)

        # -- clean-pod policy on terminal phases (reference :221-232) ---
        policy = job.clean_pod_policy
        if job.phase == api.Phase.FAILED and policy in (
            api.CleanPodPolicy.ALWAYS, api.CleanPodPolicy.ON_FAILURE
        ):
            self._clean_one(job, child_pods, svcs)
            return Result()
        if job.phase == api.Phase.COMPLETED and policy in (
            "", api.CleanPodPolicy.ALWAYS, api.CleanPodPolicy.ON_COMPLETION
        ):
            self._clean_one(job, child_pods, svcs)
            return Result()

        # -- create missing pods (reference :234-287) -------------------
        # Divergence from the reference's one-pod-per-pass cadence: the whole
        # gang is created in ONE pass. The reference re-reads the world
        # between mutations via the apiserver; here the write-through
        # informer cache gives the same read-your-writes safety, and on TPU
        # the gang's bring-up latency is the cost that matters — a slice
        # can't start until every host's pod exists, so serializing creates
        # across event-loop passes only adds idle-slice time.
        statuses = job.get_statuses()
        created_pods = 0
        for res in job.get_resource_order():
            if specs.get(res) is None:
                continue
            if not helper.is_pod_created(specs[res], statuses.get(res)):
                for i in range(specs[res]["replicas"]):
                    if self._create_pod(job, res, i):
                        created_pods += 1
        if created_pods:
            return Result()

        # -- global-env ConfigMap barrier (reference :289-306) ----------
        if job.elastic is None and helper.is_all_pods_ready(job, child_pods):
            try:
                self.client.get("ConfigMap", job.namespace, job.name)
            except NotFoundError:
                cm = helper.construct_configmap(job, child_pods)
                if cm is None:
                    return Result(requeue=True)
                k8s.set_controller_reference(job.obj, cm)
                try:
                    self._create_resource(job, cm)
                except ConflictError:
                    return Result(requeue=True)
                return Result()

        # -- ordered startup release (reference :308-330) ---------------
        if job.phase == api.Phase.STARTING and self.init_image:
            return self._coordinate_startup(job, child_pods, specs, statuses)

        return Result()

    # ------------------------------------------------------------------
    # pieces
    # ------------------------------------------------------------------

    def _elastic_preemption(self, job: api.TpuJob,
                            child_pods: List[dict]) -> Optional[Result]:
        """Whole-slice restart for elastic jobs when the kubelet reports a
        pod Failed (preemption/eviction): delete the pod so the normal
        create path replaces it, and bump the membership epoch so every
        surviving worker ends its cycle at the next step boundary and
        resumes from the latest checkpoint (a TPU slice is one collective —
        a dead host stalls everyone's ICI collectives, so partial recovery
        is not an option; SURVEY §7 "preemption vs elasticity").

        Dedup: only pods NOT already marked for deletion count — real pod
        deletion is asynchronous (grace period, finalizers, cache lag), so
        a Failed pod can linger across many passes with a
        deletionTimestamp; bumping again each pass would yank healthy
        workers through repeated restarts. A restart budget
        (status.preemptionRestarts vs helper.preemption_budget) bounds a
        deterministically-crashing container: past it, get_job_phase stops
        answering Restarting and the job fails terminally. Pods deleted
        OUTRIGHT (object gone, no Failed status) take the slower built-in
        path instead: the create path replaces them, the replacement
        rejoins, and the stalled survivors crash out of their dead
        collectives and are restarted by restartPolicy=OnFailure — correct
        but slower; the epoch bump is the fast path for the
        kubelet-reported failure this branch handles.
        """
        self._migration_upkeep(job, child_pods)
        gate = self._graceful_drain(job, child_pods)
        if gate is not None:
            return gate
        jkey = (job.namespace, job.name)
        # prune the handled-incident memory to pods that still exist, so
        # it stays bounded across recreate churn
        child_uids = {p["metadata"].get("uid") for p in child_pods}
        with self._warn_lock:
            handled = self._preempt_handled.get(jkey)
            if handled is not None:
                handled &= child_uids
                if not handled:
                    del self._preempt_handled[jkey]
            handled = set(self._preempt_handled.get(jkey, ()))
        failed = [p for p in child_pods if k8s.pod_phase(p) == "Failed"]
        if not failed:
            return None
        if helper.restart_budget_exhausted(job):
            # a budget spent: get_job_phase has gone terminal Failed — let
            # the clean-pod-policy path own the wreckage, don't restart
            return None
        fresh = [p for p in failed
                 if not p["metadata"].get("deletionTimestamp")
                 and p["metadata"].get("uid") not in handled]
        if not fresh:
            # all already deleting (or already handled — a stale cache
            # can replay a deleted Failed pod): wait for the objects to
            # go away / the resync to heal
            return Result(requeue_after=1.0)
        # Bump BEFORE deleting: once the pods are gone the next pass sees
        # no Failed pod, so a bump failure after deletion could never be
        # retried — the incident would silently lose its restart signal.
        epoch = None
        if self.kv is not None:
            try:
                epoch = bump_epoch(self.kv, job)
            except Exception as e:  # store unreachable — surface and retry
                log.error("elastic epoch bump failed: %s", e)
                return self._requeue_error((job.namespace, job.name))
        for pod in fresh:
            self._delete_resource(job, pod)
        # the incident is now owned: later passes re-serving these pods
        # from a stale cache must not count them again
        with self._warn_lock:
            self._preempt_handled.setdefault(jkey, set()).update(
                p["metadata"].get("uid") for p in fresh)
        # Increment the restart count against the FRESH object: job.obj's
        # resourceVersion is stale once the status-sync update above has
        # landed, so updating it again would conflict every time and the
        # budget would never count.
        # Classify the incident: a container that exited non-zero on its
        # own counts against the (much smaller) app-failure budget, not
        # the preemption budget — a deterministic crash must not get 10
        # patient whole-slice restarts (advisor round-4). ALL fresh pods
        # must look app-crashed: during a real eviction the SURVIVORS
        # crash out of their dead collectives with app-looking exits, so
        # any eviction evidence in the batch marks the whole incident
        # preemption.
        incident_app = all(helper.classify_pod_failure(p) == "app"
                           for p in fresh)
        field = "appFailureRestarts" if incident_app else "preemptionRestarts"
        budget = (helper.app_failure_budget(job) if incident_app
                  else helper.preemption_budget(job))
        self._count_restart_durably(job, field)
        # cause-split restart counter: preemption vs app-OOM vs app-error
        # (the same evidence the budget split keys on, one level finer)
        self.obs.observe_restart(job.namespace, job.name,
                                 incident_cause(fresh))
        self.recorder.event(
            job.obj, "Warning", "PreemptionRestart",
            "%d pod(s) failed (%s, %s); deleted for recreate%s (%s %d/%d)"
            % (len(fresh),
               ", ".join(p["metadata"]["name"] for p in fresh),
               "app crash" if incident_app else "preemption/eviction",
               "; membership epoch bumped to %s for whole-slice restart "
               "from checkpoint" % epoch if epoch else "",
               field, int(job.status[field]), budget))
        return Result(requeue=True)

    def _sched_gate(self, job: api.TpuJob) -> Optional[Result]:
        """Consult the fleet arbiter; None = admitted, fall through to
        normal reconciliation. Queue decisions requeue (the arbiter
        replans as the cluster changes) with a once-per-reason Event."""
        key = (job.namespace, job.name)
        if job.phase in (api.Phase.COMPLETED, api.Phase.FAILED):
            # a job can reach terminal while queued — drop its entry now
            # rather than waiting for object deletion
            with self._warn_lock:
                self._sched_queued.pop(key, None)
            # terminal jobs are not gated, but their teardown passes are
            # exactly when capacity frees — poke the arbiter so queued
            # admissions / parked-np restores flow without waiting for a
            # queued job's next poll
            try:
                self.arbiter.poke()
            except Exception as e:
                log.error("fleet arbiter poke failed: %s", e)
            return None
        try:
            decision = self.arbiter.decide(job)
        except Exception as e:  # arbiter read failed — surface and retry
            log.error("fleet arbiter decide failed for %s/%s: %s",
                      job.namespace, job.name, e)
            return self._requeue_error(key)
        if decision.admitted:
            if decision.np is not None:
                worker = job.spec.get(api.RES_WORKER) or {}
                if int(worker.get("replicas") or 0) != decision.np:
                    # decide() just realigned spec.worker.replicas; the
                    # object THIS pass holds predates the write — acting
                    # on it would size the gang stale (chips beyond the
                    # allocation). Requeue for a fresh read.
                    return Result(requeue=True)
            with self._warn_lock:
                was_queued = self._sched_queued.pop(key, None) is not None
            if was_queued:
                self.recorder.event(
                    job.obj, "Normal", "SchedAdmitted",
                    "admitted by the fleet arbiter")
            return None
        with self._warn_lock:
            reason_changed = self._sched_queued.get(key) != decision.reason
            if reason_changed:
                self._sched_queued[key] = decision.reason
        if reason_changed:
            self.recorder.event(job.obj, "Normal", "SchedQueued",
                                decision.reason)
        return Result(requeue_after=decision.retry_after or 1.0)

    def _feedback_remediation(self, job: api.TpuJob,
                              child_pods: List[dict]) -> Optional[Result]:
        """Apply a pending feedback decision to this job (sched/feedback
        .py): evict-and-re-gang a persistently slow member (``regang``)
        or drain the whole gang off a degraded backend (``remediate``).

        Both ride the PR 5 graceful-drain path and are BUDGET-FREE: the
        job is stamped with ANNOT_SCHED_EVICT first, so the drain books
        ``status.schedPreemptions`` (a remediation must never push a
        well-behaved job toward its restart budget). The decision is
        only consumed (``commit_remediation`` — counter + sched_feedback
        trace event) once the stamp persisted and the eviction is in
        flight; a failed stamp leaves it pending for the next pass."""
        fb = self.arbiter.feedback
        if job.phase != api.Phase.RUNNING or job.elastic is None:
            return None
        action = fb.pending_remediation(job.namespace, job.name)
        if action is None:
            return None
        live = [p for p in child_pods
                if (p["metadata"].get("annotations") or {})
                .get(api.ANNOT_RESOURCE) == api.RES_WORKER
                and k8s.pod_phase(p) in ("Pending", "Running")
                and not p["metadata"].get("deletionTimestamp")]
        if not live:
            return None  # mid-incident already; nothing to drain
        targets = live
        if action.get("action") == "regang":
            targets = []
            for pod in live:
                _res, idx = helper.extract_name_index(
                    pod["metadata"]["name"])
                if idx == action.get("worker"):
                    targets.append(pod)
            if not targets:
                # the slow member is already gone (recreating): leave
                # the decision pending — a healthy detector window for
                # the replacement clears it, acting on the new pod is
                # exactly what persistence (M more windows) is for
                return None
        if not self.arbiter.stamp_evict(job.namespace, job.name):
            return self._requeue_error((job.namespace, job.name))
        fb.commit_remediation(job.namespace, job.name, action)
        # incident inception (feedback decision): the drain this
        # decision commissions books a scheduler eviction — arm the
        # finer cause label so the incident the drain opens reads
        # regang/remediate, not a generic evict
        self.obs.incidents.arm(
            job.namespace, job.name,
            "regang" if action.get("action") == "regang" else "remediate")
        if action.get("action") == "regang":
            reason, what = "SchedFeedbackRegang", (
                "worker %s flagged as the gang straggler for %s "
                "consecutive windows (p50 %s vs gang median %s): "
                "evicting it for re-gang on a healthy host"
                % (action.get("worker"), action.get("straggler_windows"),
                   action.get("p50"), action.get("gang_median")))
        else:
            reason, what = "SchedFeedbackRemediate", (
                "backend degradation detected (throughput collapse vs "
                "the job's own baseline): draining the gang for a "
                "budget-free re-schedule off the degraded backend")
        self.recorder.event(
            job.obj, "Normal", reason,
            "%s; %d pod(s) draining gracefully (schedPreemptions are "
            "budget-free)" % (what, len(targets)))
        for pod in targets:
            self.arbiter.evictor(pod, self.arbiter.drain_grace)
        return Result(requeue=True)

    def _dest_alive(self, dest: str) -> bool:
        """Does the migration destination still exist with schedulable
        TPU chips? ``dest`` may name a Node or a pool (the GKE nodepool
        label); empty means "anywhere but the source" and is always
        satisfiable while the fleet has nodes at all."""
        try:
            nodes = self.client.list("Node")
        except Exception:
            return True  # a flaky list must not abort a healthy MOVE
        tpu = [n for n in nodes
               if int(str(((n.get("status") or {}).get("allocatable")
                           or {}).get(helper.TPU_RESOURCE, 0)) or 0) > 0]
        if not dest:
            return bool(tpu)
        for node in tpu:
            meta = node.get("metadata") or {}
            if meta.get("name") == dest:
                return True
            if (meta.get("labels") or {}).get(
                    helper.GKE_NODEPOOL_TOPOLOGY) == dest:
                return True
        return False

    def _feedback_migration(self, job: api.TpuJob,
                            child_pods: List[dict]) -> Optional[Result]:
        """Execute a pending MIGRATE decision (sched/feedback.py): the
        MOVE verb. Same commit discipline as remediation — the intent is
        stamped on the OBJECT first (:data:`helper.ANNOT_SCHED_MIGRATE`,
        so a restarted operator re-reads a MOVE in flight and the drain
        books budget-free), the decision is only consumed once the
        stamp persisted, and the gang drains through the PR 5 graceful
        path while the destination pre-stages state + compile. A
        destination that died between decision and execution aborts
        CLEANLY here: the decision is dropped (``abort_migration``),
        nothing was stamped, no budget moved — the feedback loop
        re-decides from fresh signals."""
        fb = self.arbiter.feedback
        if job.phase != api.Phase.RUNNING or job.elastic is None:
            return None
        action = fb.pending_migration(job.namespace, job.name)
        if action is None:
            return None
        dest = str(action.get("dest") or "")
        if not self._dest_alive(dest):
            fb.abort_migration(job.namespace, job.name, "dest_dead")
            self.recorder.event(
                job.obj, "Warning", "SchedFeedbackMigrateAborted",
                "migration destination %r vanished before the MOVE "
                "started; decision dropped (no budget spent)" % dest)
            return None
        live = [p for p in child_pods
                if (p["metadata"].get("annotations") or {})
                .get(api.ANNOT_RESOURCE) == api.RES_WORKER
                and k8s.pod_phase(p) in ("Pending", "Running")
                and not p["metadata"].get("deletionTimestamp")]
        if not live:
            return None  # mid-incident already; nothing to move
        # the intent the destination side needs: path + placement, plus
        # the newest checkpoint step the runner has stamped (the state
        # pre-stage key — see artifacts/state.py)
        ckpt = (job.metadata.get("annotations") or {}).get(
            "batch.tpujob.dev/latest-checkpoint-step")
        intent = {"path": action.get("path", ""),
                  "dest": dest,
                  "src": str(action.get("src") or "")}
        if ckpt is not None:
            intent["step"] = str(ckpt)
        if not self.arbiter.stamp_migrate(job.namespace, job.name,
                                          intent):
            return self._requeue_error((job.namespace, job.name))
        fb.commit_migration(job.namespace, job.name, action)
        # incident inception: the drain this MOVE commissions opens a
        # scheduler eviction — arm the migrate cause so its MTTR stages
        # (prestage/handover/warmup) book under the right label
        self.obs.incidents.arm(job.namespace, job.name, "migrate")
        if action.get("path") == "defrag":
            what = ("defragmentation: consolidating this scavenger onto "
                    "%s frees a contiguous slice for queued whale %s"
                    % (dest or "packed capacity",
                       action.get("whale", "?")))
        else:
            what = ("escaping degraded host %s (unhealthy %s consecutive "
                    "windows)" % (action.get("src", "?"),
                                  action.get("windows", "?")))
        self.recorder.event(
            job.obj, "Normal", "SchedFeedbackMigrate",
            "%s; MOVE priced below evict-and-requeue (%.1fs vs %.1fs "
            "badput); %d pod(s) draining while the destination "
            "pre-stages state + compile (schedPreemptions are "
            "budget-free)"
            % (what, float(action.get("migrate_cost_s") or 0.0),
               float(action.get("evict_cost_s") or 0.0), len(live)))
        for pod in live:
            self.arbiter.evictor(pod, self.arbiter.drain_grace)
        return Result(requeue=True)

    def _migration_upkeep(self, job: api.TpuJob,
                          child_pods: List[dict]) -> None:
        """Converge a persisted MOVE intent with reality (runs with or
        without a feedback controller — the annotation alone is
        authoritative, so this survives an operator restart):

        * the gang is Running again — the handover landed; strip the
          marker so the NEXT genuine preemption cannot misbook as a
          budget-free MOVE;
        * the destination vanished before handover — the orphaned
          intent must not pin the job in a draining state: strip it and
          fall back to the ordinary evict path (the drain, if already
          booked, was booked budget-free exactly once; the drain-ack
          dedup prevents any recount)."""
        raw = (job.metadata.get("annotations") or {}).get(
            helper.ANNOT_SCHED_MIGRATE)
        if raw is None:
            return
        fb = getattr(self.arbiter, "feedback", None) \
            if self.arbiter is not None else None
        if job.phase == api.Phase.RUNNING:
            alive = [p for p in child_pods
                     if k8s.pod_phase(p) == "Running"
                     and not p["metadata"].get("deletionTimestamp")]
            if alive:
                self._strip_job_annotation(job,
                                           helper.ANNOT_SCHED_MIGRATE)
                self.recorder.event(
                    job.obj, "Normal", "MigrationComplete",
                    "MOVE complete: the gang is running at the "
                    "destination; migration intent cleared")
            return
        try:
            intent = json.loads(raw)
        except ValueError:
            intent = {}
        dest = str(intent.get("dest") or "")
        if not self._dest_alive(dest):
            self._strip_job_annotation(job, helper.ANNOT_SCHED_MIGRATE)
            if fb is not None:
                fb.abort_migration(job.namespace, job.name,
                                   "dest_vanished")
            self.recorder.event(
                job.obj, "Warning", "MigrationAborted",
                "migration destination %r vanished before handover; "
                "falling back to the ordinary evict-resume path (the "
                "drain stays budget-free; state is untouched)" % dest)

    def _adopt_trace_context(self, job: api.TpuJob,
                             child_pods: List[dict]) -> None:
        """Re-adopt an in-flight incident when this process has none
        (fresh registry after an operator restart). The JOB-level
        trace-context annotation (kept current by
        :meth:`_sync_trace_annotation`: stamped at inception, stripped
        after close) is authoritative — it always names the NEWEST
        incident, where a pod's annotation names whatever incident
        recreated that pod and can be stale. Pods are the fallback for
        the stamp-lost-in-a-crash window. Only while the job is NOT
        Running — a steady job's pods legitimately carry the context of
        the (closed) incident that created them, and resurrecting that
        id is only correct while a recovery is actually in flight; the
        rare hook-less recovery (pods deleted outright) re-using the
        previous id is by design (``incident_restored`` marks the
        re-open, and the ledger re-opens its episode under the same id,
        so the cross-validation stays episode-wise exact)."""
        if job.phase in (api.Phase.RUNNING, api.Phase.COMPLETED,
                         api.Phase.FAILED):
            # steady or terminal: any context on the pods belongs to a
            # finished incident — resurrecting it would open a chain
            # nothing will ever close
            return
        if self.obs.incidents.context(job.namespace, job.name) is not None:
            return
        ctx = SpanContext.decode((job.metadata.get("annotations") or {})
                                 .get(helper.ANNOT_TRACE_CONTEXT))
        if ctx is not None:
            self.obs.restore_incident(job.namespace, job.name, ctx)
            return
        # Pod-annotation fallback: ONLY on this process's first sight of
        # the job (the restart window where the job-level stamp may have
        # been lost with the crash). Once this process has observed the
        # job, the actively-maintained job annotation is the sole
        # authority — pods keep the context of whatever incident created
        # them forever, and adopting one mid-run would resurrect a
        # CLOSED incident onto a new fault.
        if self.obs.has_seen(job.namespace, job.name):
            return
        for pod in child_pods:
            enc = (pod["metadata"].get("annotations") or {}).get(
                helper.ANNOT_TRACE_CONTEXT)
            ctx = SpanContext.decode(enc)
            if ctx is not None:
                self.obs.restore_incident(job.namespace, job.name, ctx)
                return

    def _sync_trace_annotation(self, job: api.TpuJob) -> None:
        """Keep the JOB's trace-context annotation equal to the open
        incident: stamped (bounded conflict retry, fresh GET per
        attempt, best-effort) while one is open, stripped once the job
        is back to Running with none — so a restarted operator adopts
        the CURRENT incident, never a closed one a stale pod annotation
        still remembers. Both writes are episodic (once per incident),
        the same write budget as ANNOT_SCHED_EVICT."""
        ctx = self.obs.incidents.context(job.namespace, job.name)
        annots = job.metadata.get("annotations") or {}
        have = annots.get(helper.ANNOT_TRACE_CONTEXT)
        if ctx is None:
            if have is not None and job.phase == api.Phase.RUNNING:
                old = SpanContext.decode(have)
                if old is not None and not self.obs.incidents.was_closed(
                        old.incident_id):
                    # this process never saw that incident close — a
                    # freshly restarted operator whose kubelet state has
                    # not caught up yet must not strip the annotation it
                    # may be about to adopt (undecodable garbage is
                    # stripped regardless)
                    return
                self._strip_job_annotation(job,
                                           helper.ANNOT_TRACE_CONTEXT)
            return
        enc = ctx.encode()
        if have == enc:
            return
        for _attempt in range(4):
            try:
                cur = self.client.get(api.KIND, job.namespace, job.name)
            except NotFoundError:
                return
            cur["metadata"].setdefault("annotations", {})[
                helper.ANNOT_TRACE_CONTEXT] = enc
            try:
                self.client.update(cur)
            except ConflictError:
                continue
            job.metadata.setdefault("annotations", {})[
                helper.ANNOT_TRACE_CONTEXT] = enc
            return

    def _count_restart_durably(self, job: api.TpuJob, field: str) -> None:
        """Increment a restart counter with bounded retry and a fresh GET
        per attempt: a lost increment under persistent status-update
        conflicts would let a deterministically-crashing container restart
        the slice past the intended budget (every pass re-reading the
        stale persisted count) — the budget must count durably, not
        best-effort. The fresh GET also carries over whatever the OTHER
        counter says in the live status, so a preemption incident racing
        an app-failure incident through a 409 retry can never wipe the
        sibling's count."""
        persisted = False
        for _attempt in range(4):
            try:
                cur = self.client.get(api.KIND, job.namespace, job.name)
                count = int(cur.get("status", {}).get(field) or 0) + 1
                cur.setdefault("status", {})[field] = count
                self.client.update_status(cur)
                job.status[field] = count
                persisted = True
                break
            except ConflictError:
                continue  # re-GET picks up the new resourceVersion
            except NotFoundError:
                break  # job deleted mid-incident: nothing to count against
        if not persisted:
            # still conflicting after retries: count in-memory so THIS
            # pass's event/budget math is right, and requeue — the next
            # pass re-reads the persisted value and the incident dedup
            # (pods already deleting / drain-acked) prevents a double
            # restart
            job.status[field] = int(job.status.get(field) or 0) + 1

    def _graceful_drain(self, job: api.TpuJob,
                        child_pods: List[dict]) -> Optional[Result]:
        """Graceful-preemption drain notice: pods turned Terminating with
        a grace window (eviction API / announced TPU maintenance — the
        kubelet has delivered SIGTERM and the runner's drain hook is
        cutting a final checkpoint). Handle the incident NOW, while the
        pods are still draining: bump the membership epoch so every
        surviving worker also checkpoints and exits at its next step
        boundary, and count one preemption restart — the drained slice
        then restores from its final step instead of losing up to
        checkpoint_every steps.

        Dedup is durable: handled pods are stamped with
        helper.ANNOT_DRAIN_ACK, so neither later passes nor a restarted
        operator re-bump the epoch for the same incident. Pods Terminating
        because of a scale-down (index >= replicas) or because the
        clean-pod policy is tearing down a TERMINAL job are the
        controller's own doing and never count as drains."""
        if job.phase in (api.Phase.COMPLETED, api.Phase.FAILED):
            # _clean_one's deletions on a finished job linger Terminating
            # on a real apiserver — they are cleanup, not preemption
            return None
        specs = job.get_specs()

        def is_drain(pod: dict) -> bool:
            meta = pod["metadata"]
            if not meta.get("deletionTimestamp"):
                return False
            if k8s.pod_phase(pod) not in ("Pending", "Running"):
                return False
            res_type, idx = helper.extract_name_index(meta["name"])
            spec = specs.get(res_type)
            # a role absent from the spec (removed/renamed) is controller
            # cleanup, the same class as an index beyond replicas
            return spec is not None and idx < spec["replicas"]

        alive = any(k8s.pod_phase(p) in ("Pending", "Running")
                    for p in child_pods)
        if (helper.ANNOT_SCHED_EVICT in
                (job.metadata.get("annotations") or {})
                and not alive):
            # The arbiter's eviction finished draining without any pass
            # observing it (operator down mid-drain, pods already gone):
            # the incident is over, and a stale marker left behind would
            # misbook the NEXT genuine preemption as budget-free. Strip
            # only when the gang is fully gone — a lagging informer
            # cache can briefly show the victim's pods as live Running
            # right after the arbiter's deletes, and stripping then
            # would spend the victim's restart budget on a voluntary
            # eviction. (Pod recreation happens later in the pass, so
            # the restarted operator strips before re-creating.)
            self._strip_job_annotation(job, helper.ANNOT_SCHED_EVICT)
        fresh = [p for p in child_pods if is_drain(p)
                 and helper.ANNOT_DRAIN_ACK
                 not in (p["metadata"].get("annotations") or {})]
        if not fresh:
            return None
        # A fleet-arbiter eviction (sched/) drains through this same path
        # but is VOLUNTARY: it books status.schedPreemptions instead of
        # spending the preemption-restart budget (the budget exists to
        # bound crash loops; a scheduler reclaiming chips must never push
        # a well-behaved job toward terminal Failed).
        sched_evict = helper.ANNOT_SCHED_EVICT in (
            job.metadata.get("annotations") or {})
        # A MOVE drains through this same path and is just as voluntary:
        # it books schedPreemptions, never the restart budget. Unlike
        # the evict marker, the migrate intent is NOT stripped here — it
        # must survive until the destination gang is Running (handover
        # complete; _migration_upkeep strips it) so a restarted operator
        # keeps executing the MOVE it finds on the object.
        sched_migrate = helper.ANNOT_SCHED_MIGRATE in (
            job.metadata.get("annotations") or {})
        if (not sched_evict and not sched_migrate
                and helper.restart_budget_exhausted(job)):
            return None
        # Bump BEFORE acking (mirror of the hard-preemption ordering): an
        # acked-but-unbumped incident could never retry its restart
        # signal, silently losing the survivors' checkpoint cue.
        epoch = None
        if self.kv is not None:
            try:
                epoch = bump_epoch(self.kv, job)
            except Exception as e:  # store unreachable — surface and retry
                log.error("elastic epoch bump failed: %s", e)
                return self._requeue_error((job.namespace, job.name))
        if not all(self._ack_drain(pod) for pod in fresh):
            # an ack that would not persist means the NEXT pass sees the
            # incident as fresh again: don't count yet, or the retry
            # would double-spend the budget — the epoch re-bump on that
            # retry is harmless (workers restart once per poll, however
            # many bumps landed in between)
            return self._requeue_error((job.namespace, job.name))
        if sched_evict or sched_migrate:
            self._count_restart_durably(job, "schedPreemptions")
            if sched_evict:
                self._strip_job_annotation(job, helper.ANNOT_SCHED_EVICT)
            if sched_migrate:
                # re-arm across an operator restart: the in-memory arm
                # from _feedback_migration died with the old process,
                # but the marker on the object says this drain is a
                # MOVE — its incident must book cause=migrate
                self.obs.incidents.arm(job.namespace, job.name,
                                       "migrate")
            self.obs.observe_sched_eviction(job.namespace, job.name)
            self.obs.observe_drain(job.namespace, job.name,
                                   pods=len(fresh))
            self.recorder.event(
                job.obj, "Normal",
                "MigrationDrain" if sched_migrate
                else "SchedulerPreempted",
                "%d pod(s) draining for the fleet arbiter (%s)%s; final "
                "checkpoints cut at the next step boundary; the job %s "
                "(schedPreemptions %d)"
                % (len(fresh),
                   ", ".join(p["metadata"]["name"] for p in fresh),
                   "; membership epoch bumped to %s" % epoch
                   if epoch else "",
                   "MOVEs to its pre-staged destination" if sched_migrate
                   else "re-queues for capacity",
                   int(job.status.get("schedPreemptions") or 0)))
            return Result(requeue=True)
        self._count_restart_durably(job, "preemptionRestarts")
        self.obs.observe_drain(job.namespace, job.name, pods=len(fresh))
        self.obs.observe_restart(job.namespace, job.name, "preemption")
        self.recorder.event(
            job.obj, "Normal", "GracefulDrain",
            "%d pod(s) draining with grace (%s)%s; final checkpoints cut "
            "at the next step boundary (preemptionRestarts %d/%d)"
            % (len(fresh),
               ", ".join(p["metadata"]["name"] for p in fresh),
               "; membership epoch bumped to %s" % epoch if epoch else "",
               int(job.status.get("preemptionRestarts") or 0),
               helper.preemption_budget(job)))
        return Result(requeue=True)

    def _strip_job_annotation(self, job: api.TpuJob, annot: str) -> None:
        """Remove a handled incident marker from the job (bounded conflict
        retry, fresh GET per attempt). If every attempt conflicts the
        marker survives one incident too long — harmless for dedup (the
        pods are already acked), and the next arbiter pass re-stamps or
        the next drain re-strips it."""
        for _attempt in range(4):
            try:
                cur = self.client.get(api.KIND, job.namespace, job.name)
            except NotFoundError:
                return
            annots = cur["metadata"].get("annotations") or {}
            if annot not in annots:
                return
            del annots[annot]
            cur["metadata"]["annotations"] = annots
            try:
                self.client.update(cur)
            except ConflictError:
                continue
            (job.metadata.get("annotations") or {}).pop(annot, None)
            return

    def _ack_drain(self, pod: dict) -> bool:
        """Stamp ANNOT_DRAIN_ACK on a draining pod (bounded conflict
        retry, fresh GET per attempt; a vanished pod needs no ack).
        False when the ack could not be persisted — the caller must not
        count the incident yet, or the next pass (which will see the
        pod as fresh again) would double-spend the budget."""
        ns = pod["metadata"].get("namespace", "default")
        name = pod["metadata"]["name"]
        for _attempt in range(4):
            try:
                cur = self.client.get("Pod", ns, name)
                annots = cur["metadata"].setdefault("annotations", {})
                if annots.get(helper.ANNOT_DRAIN_ACK):
                    return True
                annots[helper.ANNOT_DRAIN_ACK] = "true"
                self.client.update(cur)
                return True
            except ConflictError:
                continue
            except NotFoundError:
                return True
        return False

    def _sync_current_status(self, job: api.TpuJob,
                             child_pods: List[dict]) -> bool:
        """reference: syncCurrentStatus (paddlejob_controller.go:335-381).

        Returns True when the freshly derived status differs from the
        object's current one — the no-op suppression lives HERE, with the
        derivation, so no caller can forget it: at fleet scale an
        unconditional status write per pass is the biggest apiserver
        write amplifier (each write fans out a MODIFIED watch event that
        re-enqueues the key, so the queue never drains).

        The phase is derived once, from the fresh per-role statuses; the
        persisted phase seeds the sticky-terminal/no-decision fallbacks
        in helper.get_job_phase (the old double derivation — once against
        the stale roles, once against the fresh ones — was ~20%% of a
        steady-state pass for the same answer).
        """
        old_status = job.status
        new_status = {
            "phase": job.phase,  # recomputed below from the fresh roles
            "mode": helper.get_job_mode(job),
        }
        if job.status.get("startTime"):
            new_status["startTime"] = job.status["startTime"]
        if job.status.get("completionTime"):
            new_status["completionTime"] = job.status["completionTime"]
        if job.status.get("preemptionRestarts"):
            new_status["preemptionRestarts"] = job.status["preemptionRestarts"]
        if job.status.get("appFailureRestarts"):
            new_status["appFailureRestarts"] = job.status["appFailureRestarts"]
        if job.status.get("schedPreemptions"):
            new_status["schedPreemptions"] = job.status["schedPreemptions"]

        per_role = {}
        for pod in child_pods:
            res_type = pod["metadata"].get("annotations", {}).get(api.ANNOT_RESOURCE)
            if not res_type:
                continue
            ss = per_role.setdefault(
                res_type,
                {"pending": 0, "starting": 0, "running": 0,
                 "failed": 0, "succeeded": 0, "unknown": 0, "refs": []},
            )
            phase = k8s.pod_phase(pod)
            if phase == "Pending":
                if helper.is_coord_container_running(pod):
                    ss["starting"] += 1
                else:
                    ss["pending"] += 1
            elif phase == "Running":
                if helper.is_pod_real_running(pod):
                    ss["running"] += 1
                else:
                    ss["starting"] += 1
            elif phase == "Failed":
                ss["failed"] += 1
            elif phase == "Succeeded":
                ss["succeeded"] += 1
            else:
                ss["unknown"] += 1
            ss["refs"].append({
                "apiVersion": "v1",
                "kind": "Pod",
                "name": pod["metadata"]["name"],
                "namespace": pod["metadata"].get("namespace", "default"),
                "uid": pod["metadata"].get("uid", ""),
            })

        job.status = new_status
        for res_type, ss in per_role.items():
            ss = {k: v for k, v in ss.items() if v or k == "refs"}
            job.set_status(res_type, ss)
        # recompute phase/times against the fresh per-role statuses
        job.status["phase"] = helper.get_job_phase(job)
        start = helper.get_start_time(job)
        if start:
            job.status["startTime"] = start
        done = helper.get_completion_time(job)
        if done:
            job.status["completionTime"] = done
        job.status["observedGeneration"] = job.metadata.get("generation", 1)
        return new_status != old_status

    def _ensure_podgroup(self, job: api.TpuJob) -> Optional[Result]:
        """Volcano gate: create PodGroup, block pod creation until it is
        Running/Inqueue; delete it on terminal phases."""
        try:
            pg = self.client.get("PodGroup", job.namespace, job.name)
            exists = True
        except NotFoundError:
            pg, exists = None, False

        if job.phase in (api.Phase.FAILED, api.Phase.COMPLETED):
            if exists:
                self._delete_resource(job, pg)
                return Result(requeue=True)
            return None
        if not exists:
            pg = helper.construct_podgroup(job)
            k8s.set_controller_reference(job.obj, pg)
            try:
                self._create_resource(job, pg)
            except ApiError as e:
                log.error("create podgroup failed: %s", e)
            return Result(requeue=True)
        pg_phase = (pg.get("status") or {}).get("phase")
        if pg_phase not in ("Running", "Inqueue"):
            return Result(requeue=True)
        return None

    def _create_pod(self, job: api.TpuJob, res_type: str, idx: int) -> bool:
        name = helper.gen_res_name(job.name, res_type, idx)
        try:
            self.client.get("Pod", job.namespace, name)
            return False
        except NotFoundError:
            pass
        pod = helper.construct_pod(job, res_type, idx)

        if self.init_image:
            url = ""
            if self.coordination_url:
                from .coordination import release_url
                url = release_url(self.coordination_url, job.namespace, job.name, name)
            pod["spec"].setdefault("initContainers", []).append(
                helper.gen_coordinate_init_container(self.init_image, url)
            )

        if self.scheduling == helper.SCHEDULER_VOLCANO and not helper.without_volcano(job):
            pod["spec"]["schedulerName"] = helper.SCHEDULER_VOLCANO
            annots = pod["metadata"].setdefault("annotations", {})
            annots[helper.PODGROUP_ANNOTATION] = job.name
            annots[helper.VOLCANO_TASK_KEY] = res_type
            annots[helper.VOLCANO_JOB_NAME_KEY] = job.name
            annots[helper.VOLCANO_JOB_VERSION_KEY] = str(
                job.status.get("observedGeneration", 0)
            )
            sp = job.scheduling_policy
            annots[helper.VOLCANO_QUEUE_KEY] = (sp or {}).get("queue", "")

        if job.elastic is not None and self.kv is not None:
            eps = ",".join(self.kv.endpoints())
            env = pod["spec"]["containers"][0].setdefault("env", [])
            env.append({"name": "PADDLE_ELASTIC_SERVER", "value": eps})
            env.append({"name": "TPUJOB_ELASTIC_SERVER", "value": eps})

        # Incident-context propagation (docs/observability.md "Incident
        # tracing"): a pod created while its job's recovery incident is
        # open carries the operator-minted span context — the runner
        # adopts it from the env var and stamps its restore/compile/
        # first-step trace events; the annotation is what a restarted
        # operator re-reads to keep the chain's id.
        ctx = self.obs.incidents.context(job.namespace, job.name)
        if ctx is not None:
            enc = ctx.encode()
            pod["metadata"].setdefault("annotations", {})[
                helper.ANNOT_TRACE_CONTEXT] = enc
            pod["spec"]["containers"][0].setdefault("env", []).append(
                {"name": "TPUJOB_TRACE_CONTEXT", "value": enc})

        # MOVE handshake (docs/design.md "Live migration"): a pod
        # created while the job's migration intent is open is the
        # DESTINATION side — it carries the state-bundle key so the
        # runner pre-loads the source's final drain checkpoint from the
        # artifact tier before its ordinary restore (a miss simply
        # falls back to the last durable checkpoint; never a wrong
        # restore, see artifacts/state.py).
        raw = (job.metadata.get("annotations") or {}).get(
            helper.ANNOT_SCHED_MIGRATE)
        if raw is not None:
            try:
                step = json.loads(raw).get("step")
            except ValueError:
                step = None
            if step is not None:
                pod["spec"]["containers"][0].setdefault(
                    "env", []).append(
                    {"name": "TPUJOB_MIGRATE_STATE",
                     "value": "%s/%s:%s" % (job.namespace, job.name,
                                            step)})

        k8s.set_controller_reference(job.obj, pod)
        try:
            self._create_resource(job, pod)
        except ApiError as e:
            log.error("create pod failed: %s", e)
        return True

    def _coordinate_startup(self, job, child_pods, specs, statuses) -> Result:
        """Release roles in order (ps → worker → heter), reference :308-330.

        HTTP mode (production): release is pull-based — each coord init
        container polls the coordination endpoint, whose decision is a pure
        function of current pod state — so this method only keeps the requeue
        cadence while Starting (status freshness drives the frontier forward).
        Exec mode (fake-client harness): push the gate file per pass.
        """
        if self.coordination_url:
            for res in job.get_resource_order():
                st = statuses.get(res)
                if specs.get(res) is not None and (
                    st is None or st.get("running", 0) < specs[res]["replicas"]
                ):
                    return Result(requeue_after=1.0)
            return Result()
        order = job.get_resource_order()
        for i, res in enumerate(order):
            st = statuses.get(res)
            if st is None or specs.get(res) is None:
                continue
            if st.get("running", 0) < specs[res]["replicas"]:
                if (
                    i == 0
                    and st.get("running", 0) == 0
                    and not helper.is_all_coord_containers_running(child_pods)
                ):
                    return Result(requeue_after=1.0)
                for pod in child_pods:
                    annot = pod["metadata"].get("annotations", {})
                    if annot.get(api.ANNOT_RESOURCE) != res:
                        continue
                    if helper.is_coord_container_running(pod):
                        try:
                            with tracer().span(
                                    "coordination_release", job=job.name,
                                    namespace=job.namespace,
                                    pod=pod["metadata"]["name"],
                                    channel="exec"):
                                self.client.exec_in_pod(
                                    job.namespace, pod["metadata"]["name"],
                                    helper.COORD_CONTAINER_NAME,
                                    ["touch", "goon"],
                                )
                        except Exception as e:
                            # A failed release strands the whole gang in
                            # init containers (the shipped ClusterRole grants
                            # no pods/exec — the HTTP coordination channel is
                            # the production release path). Surface it where
                            # the user is looking: a Warning Event on the job
                            # — ONCE, not on every requeue pass of every pod
                            # — plus the tpujob_gang_stranded_total counter
                            # every failing pass, and requeue with the error
                            # backoff instead of hammering the apiserver at
                            # a fixed 1s cadence.
                            log.warning("exec release failed: %s", e)
                            key = (job.namespace, job.name)
                            with self._warn_lock:
                                first = key not in self._exec_release_warned
                                if first:
                                    self._exec_release_warned.add(key)
                            if first:
                                self.recorder.event(
                                    job.obj, "Warning", "ExecReleaseFailed",
                                    "exec release of %s failed: %s — the "
                                    "gang is stranded in init containers; "
                                    "the exec fallback needs a pods/exec "
                                    "RBAC rule (not in the shipped "
                                    "ClusterRole); enable the HTTP "
                                    "coordination channel "
                                    "(--coordination-bind-address) or grant "
                                    "pods/exec"
                                    % (pod["metadata"]["name"], e),
                                )
                            self.obs.observe_gang_stranded(
                                job.namespace, job.name)
                            return self._requeue_error(key)
                return Result(requeue_after=1.0)
        return Result()

    def _alloc_host_port(self, job: api.TpuJob) -> bool:
        """reference: allocHostPortForJob (:407-435). True → requeue."""
        if self.ports is None:
            return False
        annots = job.metadata.setdefault("annotations", {})
        if helper.HOST_PORT_ANNOTATION in annots:
            port = int(annots[helper.HOST_PORT_ANNOTATION])
            if self.ports.is_used(port):
                return False
            if not job.metadata.get("deletionTimestamp"):
                # controller restarted: re-learn the allocation
                self.ports.mark_used(port)
                return True
            return False
        port = self.ports.alloc()
        if port is None:
            self.recorder.event(
                job.obj, "Warning", "PortExhausted", "host port range exhausted"
            )
            return False
        annots[helper.HOST_PORT_ANNOTATION] = str(port)
        try:
            self.client.update(job.obj)
        except ApiError as e:
            log.error("persist host-port failed: %s", e)
            self.ports.release(port)
        return True

    def _finalize(self, job: api.TpuJob) -> bool:
        """Finalizer add/remove + host-port reclamation (reference :460-489)."""
        meta = job.metadata
        finalizers = meta.get("finalizers", [])
        if not meta.get("deletionTimestamp"):
            if helper.FINALIZER not in finalizers:
                meta.setdefault("finalizers", []).append(helper.FINALIZER)
                try:
                    self.client.update(job.obj)
                except ApiError:
                    return True
            return False
        if helper.FINALIZER in finalizers:
            if job.intranet == api.Intranet.HOST and self.ports is not None:
                port = meta.get("annotations", {}).get(helper.HOST_PORT_ANNOTATION)
                if port is not None and self.ports.is_used(int(port)):
                    self.ports.release(int(port))
                    return True
            meta["finalizers"] = [f for f in finalizers if f != helper.FINALIZER]
            try:
                self.client.update(job.obj)
            except ApiError:
                return True
        return False

    def _clean_one(self, job: api.TpuJob, pods: List[dict], svcs: List[dict]) -> None:
        """Delete one child per pass (reference cleanOne :198-207)."""
        for pod in pods:
            self._delete_resource(job, pod)
            return
        for svc in svcs:
            self._delete_resource(job, svc)
            return

    def _incident_attrs(self, job: api.TpuJob) -> Dict[str, str]:
        """``{"incident": id}`` while the job's recovery incident is
        open (create/delete spans join the causal chain), else empty."""
        ctx = self.obs.incidents.context(job.namespace, job.name)
        return {} if ctx is None else {"incident": ctx.incident_id}

    def _create_resource(self, job: api.TpuJob, obj: dict) -> None:
        kind, name = obj.get("kind", ""), obj["metadata"]["name"]
        try:
            with tracer().span("create", kind=kind, obj=name,
                               job=job.name, namespace=job.namespace,
                               **self._incident_attrs(job)):
                self.client.create(obj)
        except ApiError as e:
            self.recorder.event(
                job.obj, "Warning", "Create", "create failed %s %s" % (kind, name)
            )
            raise
        self.recorder.event(job.obj, "Normal", "Created", "created %s %s" % (kind, name))

    def _delete_resource(self, job: api.TpuJob, obj: dict) -> None:
        if obj["metadata"].get("deletionTimestamp"):
            return
        kind, name = obj.get("kind", ""), obj["metadata"]["name"]
        ns = obj["metadata"].get("namespace", "default")
        try:
            with tracer().span("delete", kind=kind, obj=name,
                               job=job.name, namespace=job.namespace,
                               **self._incident_attrs(job)):
                self.client.delete(kind, ns, name)
        except NotFoundError:
            return
        except ApiError:
            self.recorder.event(
                job.obj, "Warning", "Delete", "delete failed %s %s" % (kind, name)
            )
            raise
        self.recorder.event(job.obj, "Normal", "Deleted", "deleted %s %s" % (kind, name))
