"""wide_and_deep CTR training in PS mode (BASELINE config #1, live).

Runs under the operator-injected env: each pod calls this same entry;
``TRAINING_ROLE`` decides whether it serves a parameter shard
(``PSERVER``) or trains (``TRAINER``). See paddle_operator_tpu/ps.py for
the BSP protocol; the collective-mode twin is train_wide_deep.py.
"""

import logging
import os

from paddle_operator_tpu import launch, ps
from paddle_operator_tpu.models import wide_deep

logging.basicConfig(level=logging.INFO)

BATCH = int(os.environ.get("TPUJOB_BATCH", "512"))
STEPS = int(os.environ.get("TPUJOB_STEPS", "100"))
LR = float(os.environ.get("TPUJOB_LR", "0.1"))
# TPUJOB_SPARSE=1: embedding tables stay row-sharded on the pservers;
# trainers pull/push only the rows each batch touches (the CTR pattern —
# per-round traffic scales with touched rows, not table size)
SPARSE = os.environ.get("TPUJOB_SPARSE", "0") == "1"


def main():
    cfg = launch.detect_env()
    if SPARSE:
        mc = wide_deep.DEFAULT_CONFIG
        job = ps.PsTrainJob(
            init_params=lambda rng: wide_deep.init_dense(rng),
            loss_fn=wide_deep.sparse_loss_fn,
            make_batch=lambda rng, step: wide_deep.synthetic_batch(
                rng, BATCH),
            ids_fn=lambda b: wide_deep.sparse_ids(
                b, mc["vocab_per_slot"]),
            embed_dim=wide_deep.sparse_row_dim(),
            total_steps=STEPS, lr=LR,
        )
    else:
        job = ps.PsTrainJob(
            init_params=lambda rng: wide_deep.init(rng),
            loss_fn=wide_deep.loss_fn,
            make_batch=lambda rng, step: wide_deep.synthetic_batch(
                rng, BATCH),
            total_steps=STEPS,
            lr=LR,
        )
    out = ps.run_ps_training(job, cfg)
    if out["role"] == "TRAINER":
        print("final loss:", out["losses"][-1])
        if SPARSE:
            print("wire bytes: sent=%d recv=%d over %d rounds"
                  % (out["bytes_sent"], out["bytes_recv"], STEPS))


if __name__ == "__main__":
    main()
