"""BERT-base MLM training entry (multi-host collective; deploy/examples/bert.yaml)."""

import logging
import os

from paddle_operator_tpu.models import bert
from paddle_operator_tpu.ops import optim
from paddle_operator_tpu.parallel.sharding import bert_rules
from paddle_operator_tpu.runner import TrainJob, run_training

logging.basicConfig(level=logging.INFO)

BATCH = int(os.environ.get("TPUJOB_BATCH", "64"))
SEQ = int(os.environ.get("TPUJOB_SEQ", "512"))
STEPS = int(os.environ.get("TPUJOB_STEPS", "100"))


def main():
    job = TrainJob(
        init_params=lambda rng: bert.init(rng),
        loss_fn=lambda p, b: bert.loss_fn(p, b, remat=True),
        optimizer=optim.adamw(
            optim.cosine_schedule(1e-4, STEPS, STEPS // 10), weight_decay=0.01,
        ),
        make_batch=lambda rng, step: bert.synthetic_batch(rng, BATCH, SEQ),
        rules=bert_rules(),
        grad_clip=1.0,
        total_steps=STEPS,
        steps_per_call=int(os.environ.get("TPUJOB_STEPS_PER_CALL", "1")),
        checkpoint_dir=os.environ.get("TPUJOB_CHECKPOINT_DIR", ""),
    )
    out = run_training(job)
    print("final loss:", out.get("loss"))


if __name__ == "__main__":
    main()
