"""ResNet-50 training entry (collective mode; see deploy/examples/resnet.yaml).

Launched in-pod as: python -m paddle_operator_tpu.launch train_resnet.py
"""

import logging
import os

import jax

from paddle_operator_tpu.models import resnet
from paddle_operator_tpu.ops import optim
from paddle_operator_tpu.parallel.sharding import resnet_rules
from paddle_operator_tpu.runner import TrainJob, run_training

logging.basicConfig(level=logging.INFO)

BATCH = int(os.environ.get("TPUJOB_BATCH", "128"))
STEPS = int(os.environ.get("TPUJOB_STEPS", "200"))
# >1 fuses K optimizer steps into one XLA dispatch (docs/user-guide.md)
STEPS_PER_CALL = int(os.environ.get("TPUJOB_STEPS_PER_CALL", "1"))


def main():
    job = TrainJob(
        init_params=lambda rng: resnet.init(rng, depth=50, num_classes=1000),
        loss_fn=resnet.loss_fn,
        optimizer=optim.sgd(
            optim.cosine_schedule(0.4, STEPS, STEPS // 20),
            momentum=0.9, weight_decay=1e-4,
        ),
        make_batch=lambda rng, step: resnet.synthetic_batch(rng, BATCH),
        rules=resnet_rules(),
        merge_stats=resnet.merge_stats,
        total_steps=STEPS,
        steps_per_call=STEPS_PER_CALL,
        checkpoint_dir=os.environ.get("TPUJOB_CHECKPOINT_DIR", ""),
    )
    out = run_training(job)
    print("final loss:", out.get("loss"))


if __name__ == "__main__":
    main()
