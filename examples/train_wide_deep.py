"""wide_and_deep CTR training entry (PS-mode parity workload)."""

import logging
import os

from paddle_operator_tpu.models import wide_deep
from paddle_operator_tpu.ops import optim
from paddle_operator_tpu.parallel.sharding import ctr_rules
from paddle_operator_tpu.runner import TrainJob, run_training

logging.basicConfig(level=logging.INFO)

BATCH = int(os.environ.get("TPUJOB_BATCH", "512"))
STEPS = int(os.environ.get("TPUJOB_STEPS", "100"))


def main():
    job = TrainJob(
        init_params=lambda rng: wide_deep.init(rng),
        loss_fn=wide_deep.loss_fn,
        optimizer=optim.adamw(1e-3),
        make_batch=lambda rng, step: wide_deep.synthetic_batch(rng, BATCH),
        rules=ctr_rules(),
        total_steps=STEPS,
        steps_per_call=int(os.environ.get("TPUJOB_STEPS_PER_CALL", "1")),
    )
    out = run_training(job)
    print("final loss:", out.get("loss"))


if __name__ == "__main__":
    main()
