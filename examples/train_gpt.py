"""GPT causal-LM training entry (long-context; deploy/examples/gpt.yaml).

Set TPUJOB_SP>1 to shard the sequence axis over `sp` with causal ring
attention (context length scales with chips); TPUJOB_MOE_EXPERTS>0 switches
every other FFN to an expert-parallel MoE block.
"""

import functools
import logging
import os

from paddle_operator_tpu.models import gpt
from paddle_operator_tpu.ops import optim
from paddle_operator_tpu.parallel import gpt_rules, moe_rules, ring_attention
from paddle_operator_tpu.runner import TrainJob, run_training

logging.basicConfig(level=logging.INFO)

BATCH = int(os.environ.get("TPUJOB_BATCH", "16"))
SEQ = int(os.environ.get("TPUJOB_SEQ", "1024"))
STEPS = int(os.environ.get("TPUJOB_STEPS", "100"))
SP = int(os.environ.get("TPUJOB_SP", "1"))
MOE = int(os.environ.get("TPUJOB_MOE_EXPERTS", "0"))


def main():
    cfg = dict(gpt.BASE_CONFIG, max_seq=SEQ)
    for knob, key in (("TPUJOB_LAYERS", "layers"), ("TPUJOB_HIDDEN", "hidden"),
                      ("TPUJOB_HEADS", "heads"), ("TPUJOB_MLP_DIM", "mlp_dim"),
                      ("TPUJOB_VOCAB", "vocab_size")):
        if os.environ.get(knob):
            cfg[key] = int(os.environ[knob])
    if MOE:
        cfg.update(moe_experts=MOE, moe_every=2)

    # stream tokens through the LM head (never materialize [B,S,V] fp32
    # logits — gigabytes at long context); 0 restores the dense path
    ce_chunk = int(os.environ.get("TPUJOB_CE_CHUNK", "1024"))

    def loss_fn(p, b, mesh=None):
        attn = "auto"
        if mesh is not None and SP > 1 and "sp" in mesh.shape:
            attn = functools.partial(
                ring_attention, mesh=mesh, axis="sp", causal=True)
        return gpt.loss_fn(p, b, remat=True, attn_impl=attn,
                           ce_chunk=ce_chunk)

    job = TrainJob(
        init_params=lambda rng: gpt.init(rng, cfg),
        loss_fn=loss_fn,
        optimizer=optim.adamw(
            optim.cosine_schedule(3e-4, STEPS, STEPS // 10), weight_decay=0.1,
        ),
        make_batch=lambda rng, step: gpt.synthetic_batch(
            rng, BATCH, SEQ, cfg["vocab_size"]),
        rules=gpt_rules() + moe_rules(),
        mesh_axes={"dp": -1, "sp": SP} if SP > 1 else None,
        seq_axis="sp" if SP > 1 else None,
        grad_clip=1.0,
        total_steps=STEPS,
        steps_per_call=int(os.environ.get("TPUJOB_STEPS_PER_CALL", "1")),
        checkpoint_dir=os.environ.get("TPUJOB_CHECKPOINT_DIR", ""),
    )
    out = run_training(job)
    print("final loss:", out.get("loss"))


if __name__ == "__main__":
    main()
